"""Automatic crash reproduction: crash log → minimal program → C repro.

Capability parity with reference repro/repro.go:23-347: extract suspect
programs from the crash log (last executed per proc first, :136-148),
test them with escalating durations (10s then 5min, :165-183), minimize
with a still-crashes predicate (:193-200), simplify execution options
collide→threaded→sandbox→procs→repeat (:203-252), then emit + verify a
standalone C reproducer (:254-271).

The machinery that answers "does this still crash?" is pluggable: in
production it boots VMs from the pool and monitors their console (the
reference's approach); tests inject a deterministic crash oracle.
"""

from __future__ import annotations

import os
import shlex
import sys
import time
from dataclasses import dataclass, field
from typing import Callable

from syzkaller_tpu import csource
from syzkaller_tpu import prog as P
from syzkaller_tpu import vm
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys.table import SyscallTable
from syzkaller_tpu.utils import log
from syzkaller_tpu.vm.monitor import monitor_execution

# TestFn(prog_data, opts, duration) -> crashed?
TestFn = Callable[[bytes, csource.Options, float], bool]


@dataclass
class Result:
    prog: "M.Prog | None" = None
    opts: csource.Options = field(default_factory=csource.Options)
    c_repro: "str | None" = None      # C source when extraction succeeded
    duration: float = 0.0
    attempts: int = 0


def vm_test_fn(cfg, table: SyscallTable, instance_indices: list[int],
               suppressions=None) -> TestFn:
    """The production oracle: run the program via execprog inside a pool
    VM and watch the console for an oops (ref repro.go:275-304)."""
    pool: list[vm.Instance] = []

    def ensure(i: int) -> vm.Instance:
        while len(pool) <= i:
            pool.append(vm.create(cfg.type, cfg, instance_indices[len(pool)]))
        return pool[i]

    def test(data: bytes, opts: csource.Options, duration: float) -> bool:
        inst = ensure(0)
        prog_path = os.path.join(cfg.workdir, "repro.prog")
        with open(prog_path, "wb") as f:
            f.write(data)
        guest_path = inst.copy(prog_path)
        cmd = [sys.executable, "-m", "syzkaller_tpu.tools.execprog",
               "-file", guest_path, "-repeat", "0",
               "-sandbox", opts.sandbox,
               "-procs", str(opts.procs)]
        if opts.threaded:
            cmd.append("-threaded")
        if opts.collide:
            cmd.append("-collide")
        handle = inst.run(" ".join(shlex.quote(c) for c in cmd), duration)
        outcome = monitor_execution(handle, duration, ignores=suppressions,
                                    need_executing=False)
        handle.stop()
        return outcome.crashed and outcome.report is not None

    return test


def extract_suspects(crash_log: bytes, table: SyscallTable) -> list[M.Prog]:
    """Last program per proc first, then earlier ones (ref :136-148)."""
    entries = P.parse_log(crash_log, table)
    last_by_proc: dict[int, int] = {}
    for i, e in enumerate(entries):
        last_by_proc[e.proc] = i
    order: list[int] = sorted(last_by_proc.values(), reverse=True)
    rest = [i for i in range(len(entries) - 1, -1, -1) if i not in set(order)]
    return [entries[i].prog for i in order + rest]


def run(crash_log: bytes, table: SyscallTable, test_fn: TestFn,
        with_c_repro: bool = True, c_test_fn=None,
        quick: float = 10.0, thorough: float = 300.0) -> "Result | None":
    """c_test_fn(binary_path, duration) -> crashed?: when provided, the C
    reproducer is actually executed and dropped if it doesn't reproduce
    (ref repro.go:254-271); otherwise it is only verified to compile."""
    t0 = time.time()
    res = Result()
    suspects = extract_suspects(crash_log, table)
    if not suspects:
        log.logf(0, "repro: no programs in crash log")
        return None
    # starting options mirror how the fuzzer ran (threaded+collide)
    opts = csource.Options(threaded=True, collide=True, repeat=True, procs=2)

    found: "M.Prog | None" = None
    for duration in (quick, thorough):
        for p in suspects[:10]:
            res.attempts += 1
            if test_fn(P.serialize(p), opts, duration):
                found = p
                break
        if found is not None:
            break
    if found is None:
        res.duration = time.time() - t0
        log.logf(0, "repro: no suspect reproduces the crash")
        return None

    # minimize program under the crash predicate (ref :193-200)
    def pred(q: M.Prog, ci: int) -> bool:
        res.attempts += 1
        return test_fn(P.serialize(q), opts, quick)

    found, _ = P.minimize(found, -1, pred, crash_mode=True)

    # simplify options, cheapest first (ref :203-252)
    for simplify in (
        lambda o: csource.Options(**{**o.__dict__, "collide": False}),
        lambda o: csource.Options(**{**o.__dict__, "threaded": False}),
        lambda o: csource.Options(**{**o.__dict__, "procs": 1}),
        lambda o: csource.Options(**{**o.__dict__, "repeat": False}),
    ):
        cand = simplify(opts)
        res.attempts += 1
        if test_fn(P.serialize(found), cand, quick):
            opts = cand

    res.prog = found
    res.opts = opts
    if with_c_repro:
        src = csource.generate(found, opts)
        try:
            binary = csource.build(src)
        except csource.BuildError as e:
            log.logf(0, "repro: C build failed: %s", e)
            binary = None
        if binary is not None:
            try:
                if c_test_fn is not None:
                    res.attempts += 1
                    if c_test_fn(binary, quick):
                        res.c_repro = src
                    else:
                        log.logf(0, "repro: C version does not reproduce")
                else:
                    res.c_repro = src  # compiles; unverified without a VM
            finally:
                try:
                    os.unlink(binary)
                except OSError:
                    pass
    res.duration = time.time() - t0
    return res
