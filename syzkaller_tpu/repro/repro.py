"""Automatic crash reproduction: crash log → minimal program → C repro.

Capability parity with reference repro/repro.go:23-347: extract suspect
programs from the crash log (last executed per proc first, :136-148),
test them with escalating durations (10s then 5min, :165-183), minimize
with a still-crashes predicate (:193-200), simplify execution options
collide→threaded→sandbox→procs→repeat (:203-252), then emit + verify a
standalone C reproducer (:254-271).

The machinery that answers "does this still crash?" is pluggable: in
production it boots VMs from the pool and monitors their console (the
reference's approach); tests inject a deterministic crash oracle.
"""

from __future__ import annotations

import os
import shlex
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from syzkaller_tpu import csource
from syzkaller_tpu import prog as P
from syzkaller_tpu import vm
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys.table import SyscallTable
from syzkaller_tpu.utils import log
from syzkaller_tpu.vm.monitor import monitor_execution

# TestFn(prog_data, opts, duration) -> crashed?
TestFn = Callable[[bytes, csource.Options, float], bool]


# -- stateful bisection steps (the scheduler's work-unit protocol) ----------
#
# `run_steps` is a generator that yields these requests and receives
# their answers via send(): a TestBatch asks "which (if any) of these
# candidates reproduces?" (first_crasher semantics — answered with the
# earliest crashing index or None), a TestOne is a single predicate
# execution (answered with a bool).  `run` drives one machine against
# one oracle; triage.scheduler.ReproScheduler drives MANY machines
# against one shared VM pool, packing their outstanding requests into
# the same fan-out rounds.

@dataclass
class TestBatch:
    items: "list[tuple[bytes, csource.Options]]"
    duration: float
    phase: str = "suspects"


@dataclass
class TestOne:
    data: bytes
    opts: csource.Options
    duration: float
    phase: str = ""


class Oracle:
    """Crash-testing backend.  `test` answers one question; `first_crasher`
    answers many, in parallel when the backend has multiple machines
    (ref repro.go:61-116 peels 4 VMs off the fleet and boots/tests them
    concurrently).  A bare TestFn is wrapped with the serial default."""

    def __init__(self, test: TestFn, workers: int = 1):
        self.test = test
        self.workers = max(1, workers)
        # indices actually executed by the most recent first_crasher
        # call, in start order — observability for the early-cancel
        # contract (tests pin which candidates were spent)
        self.last_tested: "list[int]" = []

    def first_crasher(self, items: "list[tuple[bytes, csource.Options]]",
                      duration: float) -> "int | None":
        """Index of the earliest item that reproduces, or None.  Earlier
        items are preferred (suspects are ordered most-likely-first).
        Early-cancel: the moment the earliest *remaining* candidate is a
        confirmed crasher (every earlier index resolved without
        crashing), workers drain the queue instead of testing
        strictly-later items."""
        self.last_tested = []
        if self.workers == 1 or len(items) <= 1:
            for i, (data, opts) in enumerate(items):
                self.last_tested.append(i)
                if self.test(data, opts, duration):
                    return i
            return None
        import queue as queue_mod

        jobs: "queue_mod.Queue[int]" = queue_mod.Queue()
        for i in range(len(items)):
            jobs.put(i)
        crashed: set[int] = set()
        resolved: set[int] = set()       # tested or errored
        cancel = threading.Event()
        mu = threading.Lock()

        def finalized() -> bool:
            """Under mu: the answer can no longer improve — the
            earliest crasher has no unresolved earlier candidate."""
            if not crashed:
                return False
            m = min(crashed)
            return all(j in resolved for j in range(m))

        def worker(wid: int):
            while not cancel.is_set():
                try:
                    i = jobs.get_nowait()
                except queue_mod.Empty:
                    return
                with mu:
                    # a confirmed earlier crasher makes later items moot
                    if crashed and i > min(crashed):
                        continue
                    self.last_tested.append(i)
                try:
                    hit = self._test_on(wid, items[i][0], items[i][1],
                                        duration)
                except Exception as e:
                    # a broken machine must not silently kill the worker
                    # (and with it every suspect still queued); the item
                    # counts as resolved-no-crash so finality can land
                    log.logf(0, "repro worker %d: test failed: %s", wid, e)
                    hit = False
                with mu:
                    resolved.add(i)
                    if hit:
                        crashed.add(i)
                    if finalized():
                        cancel.set()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(min(self.workers, len(items)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return min(crashed) if crashed else None

    def test_many(self, units: "list[tuple[bytes, csource.Options, float]]"
                  ) -> "list[bool]":
        """One pool round over mixed work units: unit k runs on worker
        k (callers cap len(units) at self.workers), every verdict is
        returned — no early-cancel, the units belong to different
        consumers (the batched repro scheduler's round primitive).
        A machine error reads as no-crash, like first_crasher."""
        if len(units) == 1:
            data, opts, duration = units[0]
            try:
                return [self._test_on(0, data, opts, duration)]
            except Exception as e:
                log.logf(0, "repro worker 0: test failed: %s", e)
                return [False]
        out = [False] * len(units)

        def worker(k: int):
            data, opts, duration = units[k]
            try:
                out[k] = self._test_on(k, data, opts, duration)
            except Exception as e:
                log.logf(0, "repro worker %d: test failed: %s", k, e)

        threads = [threading.Thread(target=worker, args=(k,), daemon=True)
                   for k in range(len(units))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def _test_on(self, wid: int, data: bytes, opts, duration: float) -> bool:
        """Run one test on worker wid's machine (serial default ignores
        wid; the VM oracle pins each worker to its own instance)."""
        return self.test(data, opts, duration)


def _as_oracle(fn) -> Oracle:
    return fn if isinstance(fn, Oracle) else Oracle(fn)


@dataclass
class Result:
    prog: "M.Prog | None" = None
    opts: csource.Options = field(default_factory=csource.Options)
    c_repro: "str | None" = None      # C source when extraction succeeded
    duration: float = 0.0
    attempts: int = 0


class VmOracle(Oracle):
    """The production oracle: run programs via execprog inside pool VMs
    and watch their consoles for an oops (ref repro.go:275-304).  Each
    worker owns one instance (lazily booted), so `first_crasher` drives
    the whole peeled-off pool concurrently (ref repro.go:61-116)."""

    def __init__(self, cfg, table: SyscallTable, instance_indices: list[int],
                 suppressions=None):
        super().__init__(self._test0, workers=max(1, len(instance_indices)))
        self.cfg = cfg
        self.indices = instance_indices
        self.suppressions = suppressions
        self._pool: dict[int, vm.Instance] = {}
        self._pool_mu = threading.Lock()

    def _instance(self, wid: int) -> vm.Instance:
        with self._pool_mu:
            inst = self._pool.get(wid)
        if inst is None:
            inst = vm.create(self.cfg.type, self.cfg, self.indices[wid])
            with self._pool_mu:
                self._pool[wid] = inst
        return inst

    def _test0(self, data: bytes, opts: csource.Options,
               duration: float) -> bool:
        return self._test_on(0, data, opts, duration)

    def _test_on(self, wid: int, data: bytes, opts: csource.Options,
                 duration: float) -> bool:
        inst = self._instance(wid)
        # instance-index filename: concurrent repro jobs (each with its
        # own index block) never overwrite each other's prog files
        prog_path = os.path.join(self.cfg.workdir,
                                 f"repro-{self.indices[wid]}.prog")
        with open(prog_path, "wb") as f:
            f.write(data)
        guest_path = inst.copy(prog_path)
        cmd = [sys.executable, "-m", "syzkaller_tpu.tools.execprog",
               "-file", guest_path, "-repeat", "0",
               "-sandbox", opts.sandbox,
               "-procs", str(opts.procs)]
        if opts.threaded:
            cmd.append("-threaded")
        if opts.collide:
            cmd.append("-collide")
        handle = inst.run(" ".join(shlex.quote(c) for c in cmd), duration)
        outcome = monitor_execution(handle, duration,
                                    ignores=self.suppressions,
                                    need_executing=False)
        handle.stop()
        return outcome.crashed and outcome.report is not None

    def close(self) -> None:
        with self._pool_mu:
            insts, self._pool = list(self._pool.values()), {}
        for inst in insts:
            try:
                inst.close()
            except Exception as e:
                log.logf(1, "repro: instance close failed: %s", e)


def vm_test_fn(cfg, table: SyscallTable, instance_indices: list[int],
               suppressions=None) -> VmOracle:
    """Compatibility constructor for the production oracle."""
    return VmOracle(cfg, table, instance_indices, suppressions)


def extract_suspects(crash_log: bytes, table: SyscallTable) -> list[M.Prog]:
    """Last program per proc first, then earlier ones (ref :136-148)."""
    entries = P.parse_log(crash_log, table)
    last_by_proc: dict[int, int] = {}
    for i, e in enumerate(entries):
        last_by_proc[e.proc] = i
    order: list[int] = sorted(last_by_proc.values(), reverse=True)
    rest = [i for i in range(len(entries) - 1, -1, -1) if i not in set(order)]
    return [entries[i].prog for i in order + rest]


def run_steps(crash_log: bytes, table: SyscallTable,
              with_c_repro: bool = True, c_test_fn=None,
              quick: float = 10.0, thorough: float = 300.0):
    """The bisection state machine, inverted: yields TestBatch/TestOne
    requests, receives their answers via send(), and returns the final
    Result (or None) as StopIteration.value.  `run` drives it against
    one oracle; the triage scheduler advances many of these machines
    per shared VM-pool round."""
    t0 = time.time()
    res = Result()
    suspects = extract_suspects(crash_log, table)
    if not suspects:
        log.logf(0, "repro: no programs in crash log")
        return None
    # starting options mirror how the fuzzer ran (threaded+collide)
    opts = csource.Options(threaded=True, collide=True, repeat=True, procs=2)

    found: "M.Prog | None" = None
    for duration in (quick, thorough):
        items = [(P.serialize(p), opts) for p in suspects[:10]]
        res.attempts += len(items)
        hit = yield TestBatch(items, duration)
        if hit is not None:
            found = suspects[hit]
            break
    if found is None:
        res.duration = time.time() - t0
        log.logf(0, "repro: no suspect reproduces the crash")
        return None

    # minimize program under the crash predicate (ref :193-200),
    # one predicate execution per yielded step
    mingen = P.minimize_steps(found, -1, crash_mode=True)
    try:
        q, ci = next(mingen)
        while True:
            res.attempts += 1
            ok = yield TestOne(P.serialize(q), opts, quick,
                               phase="minimize")
            q, ci = mingen.send(bool(ok))
    except StopIteration as s:
        found, _ = s.value

    # simplify options, cheapest first (ref :203-252)
    for simplify in (
        lambda o: csource.Options(**{**o.__dict__, "collide": False}),
        lambda o: csource.Options(**{**o.__dict__, "threaded": False}),
        lambda o: csource.Options(**{**o.__dict__, "procs": 1}),
        lambda o: csource.Options(**{**o.__dict__, "repeat": False}),
    ):
        cand = simplify(opts)
        res.attempts += 1
        if (yield TestOne(P.serialize(found), cand, quick,
                          phase="simplify")):
            opts = cand

    res.prog = found
    res.opts = opts
    if with_c_repro:
        src = csource.generate(found, opts)
        try:
            binary = csource.build(src)
        except csource.BuildError as e:
            log.logf(0, "repro: C build failed: %s", e)
            binary = None
        if binary is not None:
            try:
                if c_test_fn is not None:
                    res.attempts += 1
                    if c_test_fn(binary, quick):
                        res.c_repro = src
                    else:
                        log.logf(0, "repro: C version does not reproduce")
                else:
                    res.c_repro = src  # compiles; unverified without a VM
            finally:
                try:
                    os.unlink(binary)
                except OSError:
                    pass
    res.duration = time.time() - t0
    return res


def run(crash_log: bytes, table: SyscallTable, test_fn: TestFn,
        with_c_repro: bool = True, c_test_fn=None,
        quick: float = 10.0, thorough: float = 300.0) -> "Result | None":
    """One-crash driver over `run_steps`: TestBatch requests resolve
    through the oracle's parallel first_crasher, TestOne through one
    serial predicate execution — exactly the legacy serial-bisection
    behavior.  c_test_fn(binary_path, duration) -> crashed?: when
    provided, the C reproducer is actually executed and dropped if it
    doesn't reproduce (ref repro.go:254-271); otherwise it is only
    verified to compile."""
    oracle = _as_oracle(test_fn)
    gen = run_steps(crash_log, table, with_c_repro=with_c_repro,
                    c_test_fn=c_test_fn, quick=quick, thorough=thorough)
    answer = None
    try:
        req = next(gen)
        while True:
            if isinstance(req, TestBatch):
                answer = oracle.first_crasher(req.items, req.duration)
            else:
                answer = oracle.test(req.data, req.opts, req.duration)
            req = gen.send(answer)
    except StopIteration as s:
        return s.value
