"""Automatic crash reproduction."""

from syzkaller_tpu.repro.repro import (  # noqa: F401
    Oracle, Result, VmOracle, run, vm_test_fn)
