"""Automatic crash reproduction."""

from syzkaller_tpu.repro.repro import Result, run  # noqa: F401
