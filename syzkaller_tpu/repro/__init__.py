"""Automatic crash reproduction."""

from syzkaller_tpu.repro.repro import (  # noqa: F401
    Oracle, Result, TestBatch, TestOne, VmOracle, _as_oracle, run,
    run_steps, vm_test_fn)
