"""Benchmark: device-resident signal-diff + choice-sampling throughput.

Measures the BASELINE.json north-star metric — coverage signal-diff +
corpus-priority updates per second — as one fused jitted step per batch
(pack → diff vs max cover → merge → batched ChoiceTable draw), against
the CPU baseline doing the reference's per-exec work (sorted-set
difference/union, cover/cover.go:42-102, + one prefix-sum Choose,
prog/prio.go:230-249) in numpy.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "updates/s", "vs_baseline": N}
"""

import functools
import json
import time

import numpy as np


NPCS = 1 << 16      # 64k-PC bitmap (BASELINE config #2)
NCALLS = 256
B = 256             # execs per device step
K = 512             # max PCs per exec (exec cover list, padded)
NBATCH = 8          # distinct pre-generated batches, cycled
WARM = 3
SECONDS = 4.0


def make_workload(rng):
    """Steady-state-shaped coverage: each call has a hot PC region most
    execs stay inside (little new signal), with occasional outliers."""
    call_ids = rng.integers(0, NCALLS, size=(NBATCH, B)).astype(np.int32)
    base = (call_ids.astype(np.int64) * 131) % (NPCS - 2048)
    offs = rng.integers(0, 1024, size=(NBATCH, B, K))
    rare = rng.integers(0, NPCS, size=(NBATCH, B, K))
    hot = (rng.random((NBATCH, B, K)) < 0.995)
    pc_idx = np.where(hot, base[:, :, None] + offs, rare).astype(np.int32)
    valid = rng.random((NBATCH, B, K)) < 0.9
    return call_ids, pc_idx, valid


def bench_device(call_ids, pc_idx, valid):
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.cover.engine import fuzz_step, nwords_for

    W = nwords_for(NPCS)
    step = jax.jit(functools.partial(fuzz_step, npcs=NPCS),
                   donate_argnums=(0,))
    max_cover = jnp.zeros((NCALLS, W), jnp.uint32)
    prios = jnp.full((NCALLS, NCALLS), 0.5, jnp.float32)
    enabled = jnp.ones((NCALLS,), jnp.bool_)
    key = jax.random.PRNGKey(0)
    dev_batches = [(jnp.asarray(call_ids[i]), jnp.asarray(pc_idx[i]),
                    jnp.asarray(valid[i])) for i in range(NBATCH)]
    for i in range(WARM):
        ci, pi, va = dev_batches[i % NBATCH]
        max_cover, _, has_new, nxt = step(max_cover, prios, enabled, key, ci, pi, va)
    jax.block_until_ready(max_cover)

    iters = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < SECONDS:
        ci, pi, va = dev_batches[iters % NBATCH]
        max_cover, _, has_new, nxt = step(max_cover, prios, enabled, key, ci, pi, va)
        iters += 1
    jax.block_until_ready(max_cover)
    dt = time.perf_counter() - t0
    return B * iters / dt


def bench_cpu(call_ids, pc_idx, valid):
    """Reference-shaped CPU loop: per exec, canonicalize + diff vs the
    call's max cover, union-merge on new signal, then one ChoiceTable
    draw by binary search over the prefix-sum row."""
    max_cover = [np.zeros(0, np.uint32) for _ in range(NCALLS)]
    run = np.cumsum(np.full((NCALLS, NCALLS), 500, np.int64), axis=1)
    rng = np.random.default_rng(0)

    n = 0
    t0 = time.perf_counter()
    deadline = t0 + SECONDS
    while time.perf_counter() < deadline:
        bi = n % NBATCH
        for e in range(B):
            cid = call_ids[bi, e]
            cov = np.unique(pc_idx[bi, e][valid[bi, e]].astype(np.uint32))
            diff = np.setdiff1d(cov, max_cover[cid], assume_unique=True)
            if len(diff):
                max_cover[cid] = np.union1d(max_cover[cid], diff)
            row = run[cid]
            x = rng.integers(1, row[-1] + 1)
            np.searchsorted(row, x)
        n += 1
        if time.perf_counter() - t0 > SECONDS:
            break
    dt = time.perf_counter() - t0
    return B * n / dt


def main():
    rng = np.random.default_rng(42)
    call_ids, pc_idx, valid = make_workload(rng)
    cpu_rate = bench_cpu(call_ids, pc_idx, valid)
    dev_rate = bench_device(call_ids, pc_idx, valid)
    print(json.dumps({
        "metric": "signal_diff_prio_updates_per_sec",
        "value": round(dev_rate, 1),
        "unit": "updates/s",
        "vs_baseline": round(dev_rate / cpu_rate, 3),
    }))


if __name__ == "__main__":
    main()
