"""Benchmark: device-resident signal-diff + choice-sampling throughput.

Measures the BASELINE.json north-star metrics:

1. (primary) coverage signal-diff + corpus-priority updates/sec as one
   fused jitted step per batch (pack → diff vs max cover → merge →
   batched ChoiceTable draw), against the CPU baseline doing the
   reference's per-exec work (sorted-set difference/union,
   cover/cover.go:42-102, + one prefix-sum Choose, prog/prio.go:230-249)
   in numpy — 64k-PC bitmap (BASELINE config #2).
2. the same fused step on a 1M-PC bitmap (BASELINE config #5 shape).
3. new-coverage-per-1k-exec on a fixed 10k-exec replayed workload:
   device pipeline vs the CPU sorted-set pipeline must admit the same
   inputs (device ≥ CPU) — the "quality" half of the north star.
4. corpus minimization at 100k rows (scan set-cover) and batched
   choice/corpus-row sampling at 100k corpus (BASELINE config #3).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "updates/s", "vs_baseline": N,
   "extras": {...}}
"""

import argparse
import functools
import json
import os
import subprocess
import sys
import time

import numpy as np


NPCS = 1 << 16      # 64k-PC bitmap (BASELINE config #2)
NCALLS = 256
B = 2048            # execs per device step (manager-side aggregation of
                    # many VMs' exec streams; amortizes per-step overhead)
K = 256             # max unique PCs per exec (the executor sort-dedups;
                    # matches the production map_batch cap)
NBATCH = 8          # distinct pre-generated batches, cycled
SECONDS = 4.0


def _ensure_backend() -> str:
    """Probe the default JAX backend in a SUBPROCESS (this process must
    not import jax yet — a failed backend init is cached for the
    process lifetime) and fall back to CPU when it cannot initialize,
    so the bench always emits its JSON line instead of crashing with
    `Unable to initialize backend` (BENCH_r05 rc=1, AGAIN after the
    PR-1 fix: `jax.devices()` succeeded while the first real
    `device_put` still raised — some plugins register lazily and only
    fail on first dispatch).  The probe therefore runs a REAL
    dispatch: device_put + a jitted reduction + a value fetch.

    SYZ_BENCH_FORCE_BACKEND_FAIL=1 forces the probe to fail — the
    presubmit smoke asserts the whole bench still exits 0 through the
    CPU fallback."""
    probe = ("import jax, jax.numpy as jnp; "
             "x = jax.device_put(jnp.arange(16)); "
             "v = int(jax.jit(lambda a: a.sum())(x)); "
             "assert v == 120")
    if os.environ.get("SYZ_BENCH_FORCE_BACKEND_FAIL"):
        probe = "raise RuntimeError('forced backend-init failure')"
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=300)
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False            # a wedged backend init must also fall back
    if ok:
        return ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.stderr.write("[bench] WARNING: default backend failed the "
                     "dispatch probe; falling back to JAX_PLATFORMS=cpu\n")
    return "cpu-fallback"


def _apply_smoke() -> None:
    """Seconds-scale CPU-only config for presubmit: tiny shapes, same
    code paths, same JSON schema."""
    global NPCS, B, K, NBATCH, SECONDS
    NPCS, B, K, NBATCH, SECONDS = 1 << 12, 64, 64, 2, 0.25


def make_workload(rng, npcs=None, nbatch=None, b=None):
    """Steady-state-shaped coverage: each call has a hot PC region most
    execs stay inside (little new signal), with occasional outlier
    execs.  Rows are duplicate-free (strided arithmetic sequences with
    odd stride mod a power-of-two npcs), matching the executor's
    sort-deduped KCOV output — the engine's MXU pack relies on it."""
    npcs = npcs or NPCS
    nbatch = nbatch or NBATCH
    b = b or B
    call_ids = rng.integers(0, NCALLS, size=(nbatch, b)).astype(np.int32)
    hot_start = (call_ids.astype(np.int64) * 131) % npcs
    rare = rng.random((nbatch, b)) >= 0.995
    start = np.where(rare, rng.integers(0, npcs, size=(nbatch, b)), hot_start)
    stride = np.where(rare, 2 * rng.integers(1, npcs // 4,
                                             size=(nbatch, b)) + 1, 1)
    pc_idx = ((start[:, :, None] + np.arange(K)[None, None, :]
               * stride[:, :, None]) % npcs).astype(np.int32)
    valid = rng.random((nbatch, b, K)) < 0.9
    return call_ids, pc_idx, valid


def bench_device(call_ids, pc_idx, valid, npcs=NPCS, seconds=SECONDS,
                 steps_per_call=64, chain=8):
    """Sustained fused-step throughput, honestly synced.

    Three lessons are baked in.  (a) `steps_per_call` fuzz_steps run
    inside one jit via lax.scan with scalar outputs, so per-step
    intermediates never cross the transport; the scan CYCLES through
    the pre-uploaded workload batches on device (dynamic index on the
    leading axis) because shipping steps_per_call distinct batches
    through the tunnel would hit its request-size limit and per-call
    dispatch overhead (~10ms) wants many steps per dispatch.  (b) The
    timing barrier is a HOST VALUE FETCH through the output that
    data-depends on every step (each call's carry feeds the next): on
    this backend block_until_ready can return before remote completion,
    which both inflated round-1's number ~100× and, with an unbounded
    dispatch queue, wedged the transport.  Fetching every `chain` calls
    bounds the queue while amortizing the round-trip latency."""
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.cover.engine import fuzz_step, nwords_for

    W = nwords_for(npcs)
    nbatch, b = call_ids.shape
    cis = jnp.asarray(call_ids)
    pis = jnp.asarray(pc_idx)
    vas = jnp.asarray(valid)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def multi_step(max_cover, prios, enabled, key):
        def body(carry, i):
            mc, k = carry
            bi = i % nbatch
            ci = jax.lax.dynamic_index_in_dim(cis, bi, keepdims=False)
            pi = jax.lax.dynamic_index_in_dim(pis, bi, keepdims=False)
            va = jax.lax.dynamic_index_in_dim(vas, bi, keepdims=False)
            k, sub = jax.random.split(k)
            mc, _new, has_new, nxt = fuzz_step(mc, prios, enabled, sub,
                                               ci, pi, va, npcs=npcs,
                                               assume_unique=True)
            return (mc, k), has_new.sum() + nxt[0]
        (mc, k), outs = jax.lax.scan(body, (max_cover, key),
                                     jnp.arange(steps_per_call))
        return mc, k, outs.sum()

    max_cover = jnp.zeros((NCALLS, W), jnp.uint32)
    prios = jnp.full((NCALLS, NCALLS), 0.5, jnp.float32)
    enabled = jnp.ones((NCALLS,), jnp.bool_)
    key = jax.random.PRNGKey(0)
    max_cover, key, out = multi_step(max_cover, prios, enabled, key)
    int(out)                             # compile + warm, real barrier

    calls = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        max_cover, key, out = multi_step(max_cover, prios, enabled, key)
        calls += 1
        if calls % chain == 0:
            int(out)                     # true completion of the chain
    int(out)
    dt = time.perf_counter() - t0
    return b * steps_per_call * calls / dt


def bench_cpu(call_ids, pc_idx, valid, seconds=SECONDS):
    """Reference-shaped CPU loop: per exec, canonicalize + diff vs the
    call's max cover, union-merge on new signal, then one ChoiceTable
    draw by binary search over the prefix-sum row."""
    max_cover = [np.zeros(0, np.uint32) for _ in range(NCALLS)]
    run = np.cumsum(np.full((NCALLS, NCALLS), 500, np.int64), axis=1)
    rng = np.random.default_rng(0)

    n = 0
    t0 = time.perf_counter()
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        bi = n % NBATCH
        for e in range(B):
            cid = call_ids[bi, e]
            cov = np.unique(pc_idx[bi, e][valid[bi, e]].astype(np.uint32))
            diff = np.setdiff1d(cov, max_cover[cid], assume_unique=True)
            if len(diff):
                max_cover[cid] = np.union1d(max_cover[cid], diff)
            row = run[cid]
            x = rng.integers(1, row[-1] + 1)
            np.searchsorted(row, x)
        n += 1
        if time.perf_counter() - t0 > seconds:
            break
    dt = time.perf_counter() - t0
    return B * n / dt


def bench_new_cov_quality(rng, nexecs=16 * B):
    """Fixed replayed-corpus run (BASELINE config #3 shape): the device
    pipeline and the CPU sorted-set pipeline process the same exec
    stream in the same order; compare new-coverage verdicts per 1k execs
    and wall time.  Device must admit at least what the CPU path admits.

    The device path is the production ZERO-COPY INGEST one: raw covers
    sit in the executor's pinned PC ring (ipc/ring.py — written here
    once, untimed, exactly as the executor would), and the timed loop
    is the fuzzer's steady state: read a zero-copy slab window, dispatch
    ONE fused translate+pack+diff+merge step (PcMap translation runs ON
    DEVICE against the sorted key mirror), resolve the previous batch —
    pipelined, no host packing, no Python list materialization.  The
    previous host-packed streaming path (`engine.update_stream`) is kept
    as `replay_execs_per_sec_device_hostpack` for trajectory; round 2's
    per-batch synchronous path is what lost to CPU 4× (BENCH_r02).

    `ingest_host_dispatches_per_exec` pins the O(1)-dispatch contract:
    measured at full and half workload, the per-exec dispatch count
    must not grow with slab count (`ingest_dispatches_const`)."""
    import jax.numpy as jnp

    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.pcmap import DeviceKeyMirror, PcMap
    from syzkaller_tpu.ipc import ring as ring_mod

    nbatch = nexecs // B
    call_ids, pc_idx, valid = make_workload(rng, nbatch=nbatch)
    # raw-PC view of the workload: keep distinct PCs inside the PcMap's
    # direct space so hashed-overflow aliasing can't blur the
    # device-vs-CPU admitted-set comparison
    pc_idx = pc_idx % np.int32(NPCS - 2048)

    # CPU pipeline (best of 3, like the device side)
    cpu_dt = float("inf")
    covers = [[None] * B for _ in range(nbatch)]
    for bi in range(nbatch):
        for e in range(B):
            covers[bi][e] = np.unique(
                pc_idx[bi, e][valid[bi, e]].astype(np.uint32))
    for _ in range(3):
        t0 = time.perf_counter()
        max_cover = [np.zeros(0, np.uint32) for _ in range(NCALLS)]
        cpu_new = 0
        for bi in range(nbatch):
            for e in range(B):
                cid = call_ids[bi, e]
                cov = np.unique(pc_idx[bi, e][valid[bi, e]]
                                .astype(np.uint32))
                diff = np.setdiff1d(cov, max_cover[cid],
                                    assume_unique=True)
                if len(diff):
                    cpu_new += 1
                    max_cover[cid] = np.union1d(max_cover[cid], diff)
        cpu_dt = min(cpu_dt, time.perf_counter() - t0)

    eng = CoverageEngine(npcs=NPCS, ncalls=NCALLS, corpus_cap=8,
                         batch=B, max_pcs_per_exec=K)
    pm = PcMap(NPCS)
    mirror = DeviceKeyMirror(pm, put=eng.put_replicated)
    # steady-state ingest: the key universe is already mapped (a live
    # fuzzer reaches this within seconds — first-sight keys are a
    # cold-start transient the DeviceSignal fix-up path owns)
    pm.preseed(np.unique(np.concatenate(
        [c for row in covers for c in row if len(c)])))
    mirror.refresh()

    def fill_ring(ring):
        w = ring_mod.RingWriter(ring)
        for bi in range(nbatch):
            for e in range(B):
                if len(covers[bi][e]):
                    w.write(int(call_ids[bi, e]), covers[bi][e])
        return w.stat_written

    def drain(reader, max_slabs):
        """The fuzzer's steady-state ingest loop: zero-copy window →
        fused dispatch → pipelined resolve.  Returns (execs-with-new,
        dispatches)."""
        new = 0
        dispatches = 0
        prev = None
        while True:
            batch = reader.read_batch(max_slabs=max_slabs)
            if batch is None:
                break
            res = eng.ingest_update_slabs(batch.win, batch.counts,
                                          batch.tags, mirror)
            dispatches += 1
            if prev is not None:
                pb, pres = prev
                new += int(np.asarray(pres.has_new).sum())
                assert not np.asarray(pres.miss_rows).any()
                reader.consume(pb)
            prev = (batch, res)
        if prev is not None:
            pb, pres = prev
            new += int(np.asarray(pres.has_new).sum())
            reader.consume(pb)
        return new, dispatches

    nslabs_expected = sum(1 for row in covers for c in row if len(c))

    def ring_for(n_slabs):
        import tempfile
        path = os.path.join(tempfile.mkdtemp(prefix="syz-bench-ring-"),
                            "ring")
        # min_bucket = K bucket: ONE uniform bucket → maximal committed
        # runs; data sized so a full replay tiles the ring exactly and
        # repeated fills never wrap mid-run (a mid-run wrap would split
        # a batch and perturb the warmed dispatch shapes)
        kb = 1
        while kb < K:
            kb *= 2
        return ring_mod.PcRing.create(
            path, data_words=max(n_slabs, 8) * kb,
            index_slots=max(64, n_slabs), slab_cap=K, min_bucket=kb)

    # warm pass: compiles the dispatch shapes AND inserts every key
    # (steady state afterwards: zero misses, zero recompiles)
    ring = ring_for(nslabs_expected)
    nslabs = fill_ring(ring)
    reader = ring_mod.RingReader(ring)
    drain(reader, max_slabs=2048)
    eng.max_cover = jnp.zeros_like(eng.max_cover)

    # timed passes (best of 3, like the CPU side)
    dev_dt = float("inf")
    for _ in range(3):
        fill_ring(ring)
        t0 = time.perf_counter()
        dev_new, dispatches = drain(reader, max_slabs=2048)
        dev_dt = min(dev_dt, time.perf_counter() - t0)
        eng.max_cover = jnp.zeros_like(eng.max_cover)
    ring.close()

    # O(1)-dispatch pin: per-exec dispatch count at half the workload
    # must match (dispatches scale with batches, not slabs)
    half = nexecs // 2
    ring2 = ring_for(max(half, 8))
    w2 = ring_mod.RingWriter(ring2)
    n2 = 0
    for bi in range(nbatch):
        for e in range(B):
            if n2 >= half:
                break
            if len(covers[bi][e]):
                w2.write(int(call_ids[bi, e]), covers[bi][e])
                n2 += 1
    reader2 = ring_mod.RingReader(ring2)
    _new2, disp2 = drain(reader2, max_slabs=2048)
    ring2.close()
    eng.max_cover = jnp.zeros_like(eng.max_cover)
    per_exec = dispatches / max(nexecs, 1)
    per_exec_half = disp2 / max(half, 1)

    # the previous host-packed streaming path, for trajectory
    hn = eng.update_stream(call_ids, pc_idx, valid)      # warm compile
    np.asarray(hn)
    hp_dt = float("inf")
    for _ in range(3):
        eng.max_cover = jnp.zeros_like(eng.max_cover)
        t0 = time.perf_counter()
        np.asarray(eng.update_stream(call_ids, pc_idx, valid))
        hp_dt = min(hp_dt, time.perf_counter() - t0)
    return {
        "new_cov_per_1k_exec_device": round(dev_new / (nexecs / 1000), 2),
        "new_cov_per_1k_exec_cpu": round(cpu_new / (nexecs / 1000), 2),
        "replay_execs_per_sec_device": round(nexecs / dev_dt, 1),
        "replay_execs_per_sec_cpu": round(nexecs / cpu_dt, 1),
        "replay_execs_per_sec_device_hostpack": round(nexecs / hp_dt, 1),
        "replay_device_vs_cpu": round(cpu_dt / dev_dt, 2),
        "ingest_host_dispatches_per_exec": round(per_exec, 5),
        # the O(1) contract: growing the slab count must not grow the
        # per-exec dispatch count (amortization only improves)
        "ingest_dispatches_const": bool(
            per_exec <= per_exec_half * 1.1 + 1e-4),
        "ingest_slabs_replayed": nslabs,
    }


def bench_corpus_scale(rng, C=100_000):
    """BASELINE config #3 shape: 100k-row corpus.  Times the scan
    set-cover minimization and batched corpus-row + choice sampling."""
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.cover.engine import (
        minimize_cover_scan, nwords_for, sample_calls)

    W = nwords_for(NPCS)
    # synthetic corpus: clustered rows so minimization has structure
    key = jax.random.PRNGKey(1)
    mat = jax.random.randint(key, (C, W), 0, 1 << 30, dtype=jnp.int32
                             ).astype(jnp.uint32)
    # mask most bits off so rows are sparse-ish (realistic signal rows)
    mat = jnp.where(jax.random.uniform(key, (C, W)) < 0.02, mat, 0)
    active = jnp.ones((C,), bool)
    fn = jax.jit(minimize_cover_scan)
    keep = fn(mat, active)
    int(keep.sum())                     # compile + VALUE barrier
    t0 = time.perf_counter()
    keep = fn(mat, active)
    kept = int(keep.sum())              # block_until_ready lies on this
    min_dt = time.perf_counter() - t0   # backend; fetch a value instead

    # batched choice-table draws (the per-mutation decision stream):
    # like the production fused step, many draw batches run per dispatch
    # (lax.scan) with a value-fetch barrier — per-call dispatch overhead
    # (~10ms on this tunnel) otherwise swamps the draw itself
    probs = jnp.full((NCALLS, NCALLS), 0.5, jnp.float32)
    enabled = jnp.ones((NCALLS,), bool)
    prev = jnp.asarray(rng.integers(0, NCALLS, 4096).astype(np.int32))
    SDRAW = 64

    @jax.jit
    def draw_many(key, prev):
        def body(carry, _):
            k, pv = carry
            k, sub = jax.random.split(k)
            nxt = sample_calls(sub, probs, pv, enabled)
            return (k, nxt), nxt[0]
        (k, pv), outs = jax.lax.scan(body, (key, prev), None, length=SDRAW)
        return pv, outs.sum()

    pv, out = draw_many(key, prev)
    int(out)
    t0 = time.perf_counter()
    iters = 0
    while time.perf_counter() - t0 < 2.0:
        pv, out = draw_many(jax.random.fold_in(key, iters), pv)
        iters += 1
        if iters % 8 == 0:
            int(out)
    int(out)
    draw_rate = 4096 * SDRAW * iters / (time.perf_counter() - t0)
    return {
        "minimize_100k_rows_sec": round(min_dt, 3),
        "minimize_100k_kept": kept,
        "choice_draws_per_sec": round(draw_rate, 1),
    }


def bench_device_sparse(call_ids, pc_idx, valid, npcs, block_words=2,
                        seconds=SECONDS, steps_per_call=64, chain=8):
    """The word-block-sparse fused step on the same workload shape as
    bench_device: per-batch touched blocks are precomputed host-side
    (in production the engine does this per dispatch), the scan gathers
    only those blocks, diffs/merges at the gathered width, and scatters
    back.  Same harness discipline as bench_device: pre-uploaded cycled
    batches, scalar scan outputs, value-fetch barriers every `chain`
    calls."""
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.cover.engine import (
        nwords_for, sample_calls, sparse_update)

    W = nwords_for(npcs)
    nbatch, b = call_ids.shape
    bits = block_words * 32
    nblk = W // block_words
    raw = []
    for bi in range(nbatch):
        ok = valid[bi] & (pc_idx[bi] >= 0) & (pc_idx[bi] < npcs)
        raw.append(np.unique(pc_idx[bi][ok] // bits))
    mb = max(len(r) for r in raw)
    per = max(1, 64 // block_words)           # keep MB*block_words 64-aligned
    mb = -(-mb // per) * per
    blocks = np.full((nbatch, mb), nblk, np.int32)
    for bi, r in enumerate(raw):
        blocks[bi, : len(r)] = r

    cis = jnp.asarray(call_ids)
    pis = jnp.asarray(pc_idx)
    vas = jnp.asarray(valid)
    bls = jnp.asarray(blocks)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def multi_step(max_cover, prios, enabled, key):
        def body(carry, i):
            mc, k = carry
            bi = i % nbatch
            ci = jax.lax.dynamic_index_in_dim(cis, bi, keepdims=False)
            pi = jax.lax.dynamic_index_in_dim(pis, bi, keepdims=False)
            va = jax.lax.dynamic_index_in_dim(vas, bi, keepdims=False)
            bl = jax.lax.dynamic_index_in_dim(bls, bi, keepdims=False)
            k, sub = jax.random.split(k)
            mc, _new, has_new = sparse_update(mc, ci, pi, va, bl, npcs,
                                              block_words)
            nxt = sample_calls(sub, prios, ci, enabled)
            return (mc, k), has_new.sum() + nxt[0]
        (mc, k), outs = jax.lax.scan(body, (max_cover, key),
                                     jnp.arange(steps_per_call))
        return mc, k, outs.sum()

    max_cover = jnp.zeros((NCALLS, W), jnp.uint32)
    prios = jnp.full((NCALLS, NCALLS), 0.5, jnp.float32)
    enabled = jnp.ones((NCALLS,), jnp.bool_)
    key = jax.random.PRNGKey(0)
    max_cover, key, out = multi_step(max_cover, prios, enabled, key)
    int(out)                             # compile + warm, real barrier

    calls = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        max_cover, key, out = multi_step(max_cover, prios, enabled, key)
        calls += 1
        if calls % chain == 0:
            int(out)                     # true completion of the chain
    int(out)
    dt = time.perf_counter() - t0
    return b * steps_per_call * calls / dt


def bench_decision_stream(seconds=SECONDS, smoke=False):
    """The fused decision-stream path vs the 430-510k/s legacy draw
    metric (`choice_draws_per_sec` in bench_corpus_scale, kept for
    trajectory continuity): one megakernel dispatch emits per-context
    choice draws for EVERY prev row + the hot-row extension + corpus
    picks + an entropy slab, with the PRNG key donated on device and
    zero host operands moving in.  Measured two ways: (a) raw pipelined
    production — dispatch block N+1, resolve block N (the double-buffer
    the prefetcher runs), draws per wall-second; (b) consumer health —
    threads hammering choose() through the live prefetcher, reporting
    the underrun rate (ring misses that fell back to a direct draw)."""
    import threading as _threading

    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.device_ct import DecisionStream

    eng = CoverageEngine(npcs=NPCS, ncalls=NCALLS, corpus_cap=256,
                         batch=64, max_pcs_per_exec=K)
    per_row = 64 if smoke else 256
    hot = 128 if smoke else 2048
    stream = DecisionStream(eng, per_row=per_row, hot_slots=hot,
                            corpus_rows=64 if smoke else 256,
                            entropy_words=1024 if smoke else 1 << 13,
                            warm_after=0, autostart=False)
    # (a) raw production rate, double-buffered, value-fetch barriers
    with stream._mu:
        hot_dev = stream._hot_dev
    blk = eng.decision_block(hot_dev, stream.per_row, stream.n_rows,
                             stream.n_entropy)
    np.asarray(blk.base)                 # compile + warm, real barrier
    calls = 0
    prev_blk = None
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        nxt = eng.decision_block(hot_dev, stream.per_row, stream.n_rows,
                                 stream.n_entropy)
        if prev_blk is not None:
            np.asarray(prev_blk.base)    # resolve N while N+1 runs
        prev_blk = nxt
        calls += 1
    np.asarray(prev_blk.base)
    dt = time.perf_counter() - t0
    fused_rate = stream.draws_per_block * calls / dt

    # (b) the live prefetcher under consumer load (this is also the
    # --smoke exercise of the async refill/invalidate lifecycle)
    live = DecisionStream(eng, per_row=per_row, hot_slots=hot,
                          corpus_rows=64 if smoke else 256,
                          entropy_words=1024 if smoke else 1 << 13,
                          warm_after=0)
    live.refill_once()                   # warm ring before the clock
    run_s = 0.25 if smoke else 1.0
    stop_at = time.perf_counter() + run_s
    prevs = [-1, 0, 1, 2, 3]

    def consume(k):
        i = 0
        while time.perf_counter() < stop_at:
            live.choose(prev_call_id=prevs[(i + k) % len(prevs)])
            i += 1

    ts = [_threading.Thread(target=consume, args=(k,))
          for k in range(2 if smoke else 4)]
    for t in ts:
        t.start()
    live.invalidate()                    # mid-storm eager redraw
    for t in ts:
        t.join()
    served, under = live.stat_served, live.stat_underruns
    live.stop()
    return {
        "choice_draws_per_sec_fused": round(fused_rate, 1),
        "choice_stream_underrun_rate": round(under / max(served, 1), 4),
        "choice_stream_blocks": live.stat_blocks,
    }


def bench_admission(n_inputs=1536, nthreads=48, admit_batch=64, npcs=NPCS):
    """Batched admission through the manager coalescer vs the old
    serial per-input rpc_new_input path: N handler threads fire
    distinct NewInputs (disjoint cover ranges, so the admitted set is
    order-independent) at a live manager, once with admit_batch<=1
    (serial: one device round-trip per input) and
    once with the coalescer (fused batched dispatches).  Handlers are
    invoked directly — the RPC socket layer is byte-identical for both
    paths and exercised by the concurrent-admission test.

    Cover ranges must stay inside the PcMap's direct index space
    (n_inputs * 32 + warm < npcs - overflow_reserve): beyond it the
    hashed-overflow region aliases distinct PCs, which makes admission
    order-dependent and the serial-vs-coalesced set comparison
    meaningless."""
    import tempfile
    import threading

    from syzkaller_tpu import rpc as rpc_mod
    from syzkaller_tpu.manager.config import Config
    from syzkaller_tpu.manager.manager import Manager

    def one_run(batch_size, telemetry=True):
        wd = tempfile.mkdtemp(prefix="syz-bench-adm-")
        cfg = Config(workdir=wd, type="local", count=1, procs=1,
                     descriptions="probe.txt", npcs=npcs, http="",
                     corpus_cap=max(4 * n_inputs, 1 << 12),
                     admit_batch=batch_size, telemetry=telemetry)
        mgr = Manager(cfg)

        def mk_payloads(base, per):
            out = []
            for t in range(nthreads):
                ps = []
                for i in range(per):
                    j = base + t * per + i
                    ps.append({"name": f"vm{t}",
                               "prog": rpc_mod.b64(b"prog-%d" % j),
                               "call": "mmap", "call_index": 0,
                               "cover": [1000 + j * 64 + x
                                         for x in range(32)]})
                out.append(ps)
            return out

        def fire(ps):
            for p in ps:
                mgr.rpc_new_input(p)

        def burst(payloads):
            ts = [threading.Thread(target=fire, args=(payloads[t],))
                  for t in range(nthreads)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return time.perf_counter() - t0

        # warm with the same concurrency pattern as the timed run, so
        # the steady-state pow2 dispatch buckets are compiled up front
        n_warm = nthreads * 2
        burst(mk_payloads(10_000_000, 2))
        per = n_inputs // nthreads
        dt = burst(mk_payloads(0, per))
        admitted = len(mgr.corpus) - n_warm
        # the section's telemetry snapshot rides the emitted JSON: the
        # fused-dispatch counts and admission latency histogram are the
        # in-process evidence behind the throughput number
        snap = mgr.telemetry_snapshot(traces=0) if telemetry else None
        mgr.stop()
        return admitted, n_inputs / dt, snap

    serial_admitted, serial_rate, _ = one_run(1)
    # telemetry-overhead check (acceptance: <5% regression with the
    # device stat vector + registry on): interleaved best-of-2 per
    # config — single runs swing ±20% with scheduler/link weather and
    # the metric is pipeline capability, not transient noise
    coal_rate = off_rate = 0.0
    snap = None
    for _ in range(2):
        coal_admitted, r_on, s = one_run(admit_batch)
        assert serial_admitted == coal_admitted, \
            f"admission sets diverge: {serial_admitted} vs {coal_admitted}"
        if r_on > coal_rate:
            coal_rate, snap = r_on, s
        off_admitted, r_off, _ = one_run(admit_batch, telemetry=False)
        assert off_admitted == coal_admitted, \
            f"admission sets diverge: {off_admitted} vs {coal_admitted}"
        off_rate = max(off_rate, r_off)
    return {
        "admissions_per_sec": round(coal_rate, 1),
        "admissions_per_sec_serial": round(serial_rate, 1),
        "admission_speedup": round(coal_rate / serial_rate, 2),
        "admissions_per_sec_no_telemetry": round(off_rate, 1),
        "telemetry_overhead_pct": round(
            100.0 * (1.0 - coal_rate / off_rate), 1),
    }, snap


def bench_triage(rng, n_reports=10_000, smoke=False):
    """Crash-intelligence dedup at production volume: n synthetic
    parsed reports (oops-corpus-shaped generator, ~40 distinct crash
    templates under title/frame noise) clustered through the signature
    kernel.  The similarity matmul + threshold-union-find run as ONE
    fused dispatch per batch; warm batches are CompileCounter-pinned at
    zero recompiles.  Reported end-to-end (featurize + dispatch + label
    fetch) and kernel-only."""
    from syzkaller_tpu.telemetry import DeviceStats
    from syzkaller_tpu.triage import CrashIndex, SignatureKernel
    from syzkaller_tpu.triage import synth
    from syzkaller_tpu.vet.runtime import CompileCounter

    n = 256 if smoke else n_reports
    reports = synth.reports(rng, n)
    ds = DeviceStats()
    kern = SignatureKernel(telemetry=ds)
    feats = kern.featurize(reports)
    kern.cluster(feats)                     # compile + warm the bucket
    with CompileCounter() as cc:
        t0 = time.perf_counter()
        labels = kern.cluster(feats)
        kern_dt = time.perf_counter() - t0
    nclusters = len(set(int(x) for x in labels))
    # end-to-end through the incremental index (the manager's
    # save_crash path at batch width): featurize + one fused dispatch
    idx = CrashIndex(kernel=kern)
    t0 = time.perf_counter()
    idx.assign(reports)
    e2e_dt = time.perf_counter() - t0
    return {
        "triage_dedup_reports_per_sec": round(n / e2e_dt, 1),
        "triage_kernel_reports_per_sec": round(n / kern_dt, 1),
        "triage_batch_reports": n,
        "triage_clusters": nclusters,
        "triage_warm_recompiles": cc.count,
        "triage_telemetry": {
            k: v for k, v in ds.snapshot().items() if "triage" in k},
    }


def bench_repro_rounds(smoke=False):
    """Batched-bisection repro: N crashes against one W-worker oracle
    pool via the triage scheduler, vs N serial `repro.run` bisections.
    The headline is rounds per crash — wall rounds a VM pool must turn
    — which the scheduler holds near the deepest single machine
    instead of the serial sum."""
    import math

    from syzkaller_tpu import repro as repro_pkg
    from syzkaller_tpu.sys.table import load_table
    from syzkaller_tpu.triage import ReproScheduler

    table = load_table(files=["probe.txt"])
    N = 3 if smoke else 12
    W = 4 if smoke else 8
    markers = [b"0xdead%04x" % i for i in range(N)]

    def make_log(marker):
        return (b"executing program 0:\n"
                b"syz_probe$ints(0x1, 0x2, 0x3, 0x4, 0x5)\n"
                b"executing program 1:\n"
                b"syz_probe$ints(" + marker + b", 0x2, 0x3, 0x4, 0x5)\n"
                b"syz_probe()\n"
                b"[ 2.0] BUG: KASAN: use-after-free in foo+0x1/0x2\n")

    def crashes(data, opts, duration):
        return any(m in data for m in markers)

    class PoolOracle(repro_pkg.Oracle):
        def __init__(self):
            super().__init__(crashes, workers=W)

    done = []
    sched = ReproScheduler(PoolOracle(), table, with_c_repro=False,
                           on_done=lambda t, d, r, j: done.append(r))
    t0 = time.perf_counter()
    for i, m in enumerate(markers):
        sched.submit(make_log(m), f"bench-crash-{i}", "")
    sched.join(timeout=120)
    batched_dt = time.perf_counter() - t0
    rounds, tests = sched.stat_rounds, sched.stat_tests
    sched.stop()
    assert len(done) == N and all(
        r is not None and r.prog is not None for r in done), \
        "repro scheduler failed to reproduce the bench crashes"

    serial_rounds = 0
    for m in markers:
        calls = [0]

        def counting(data, opts, duration, calls=calls):
            calls[0] += 1
            return crashes(data, opts, duration)

        repro_pkg.run(make_log(m), table, counting, with_c_repro=False,
                      quick=0.001, thorough=0.002)
        serial_rounds += calls[0]
    return {
        "repro_rounds_per_crash": round(rounds / N, 2),
        "repro_rounds_per_crash_serial": round(serial_rounds / N, 2),
        "repro_round_speedup": round(serial_rounds / max(rounds, 1), 2),
        "repro_round_bound": math.ceil(tests / W) + serial_rounds // N,
        "repro_batched_wall_sec": round(batched_dt, 3),
    }


def bench_campaign(smoke=False):
    """Campaign-plane costs: `campaign_swap_seconds` measures
    invalidate→first steered block — the warm overlay-swap latency of
    rotating the decision stream onto another campaign through the
    epoch path — with a CompileCounter pin proving the rotate-through-
    all-campaigns storm compiles NOTHING warm (overlay operands are
    fixed (C,) shapes; only contents change).  Per-campaign
    `new_cov_per_1k_exec` replays a synthetic steered frontier through
    the fused admission gate (each campaign owns a disjoint PC
    subrange) and reads the scheduler's EWMA — the rotation-trigger
    gauge, exercised end to end."""
    from syzkaller_tpu.campaign import (CampaignScheduler,
                                        available_campaigns, load_campaign)
    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.device_ct import DecisionStream
    from syzkaller_tpu.sys.table import load_table
    from syzkaller_tpu.vet.runtime import CompileCounter

    table = load_table()
    names = available_campaigns()
    eng = CoverageEngine(npcs=1 << 13, ncalls=table.count,
                         corpus_cap=2048)
    camps = {n: load_campaign(n, table) for n in names}
    ovs = {n: eng.make_overlay(n, camps[n].boost, camps[n].enabled_ids)
           for n in names}
    stream = DecisionStream(eng, per_row=16, hot_slots=64, corpus_rows=32,
                            entropy_words=1024, autostart=False)
    for n in names:                       # warm: one compile total
        stream.set_overlay(ovs[n])
        stream.refill_once()
    times = []
    with CompileCounter() as cc:
        for _ in range(2 if smoke else 6):
            for n in names:               # the rotation storm
                t0 = time.perf_counter()
                stream.set_overlay(ovs[n])
                stream.refill_once()      # first steered block lands
                times.append(time.perf_counter() - t0)

    now = [0.0]
    sched = CampaignScheduler(names, tau=30.0, now=lambda: now[0])
    rng = np.random.default_rng(5)
    per = {}
    nb_batches = 4 if smoke else 16
    for i, n in enumerate(names):
        conn = f"vm{i}"
        sched.assign(conn)                # round-robin = names order
        base = 500 + i * 2500
        for _ in range(nb_batches):
            now[0] += 1.0
            idx = rng.integers(base, base + 800, size=(8, 32)).astype(
                np.int32)
            cids = rng.integers(0, table.count, size=8).astype(np.int32)
            _hn, _rows, nb = eng.admit_if_new(
                cids, idx, np.ones_like(idx, bool), with_new_bits=True)
            sched.note_execs(conn, 1000 // nb_batches)
            sched.note_new_cov(conn, int(nb.sum()))
        per[n] = round(sched.new_cov_per_1k_exec(n), 2)
    return {
        "campaign_swap_seconds": round(float(np.median(times)), 4),
        "campaign_swap_recompiles": cc.count,
        "new_cov_per_1k_exec": dict(
            per, all=round(sched.new_cov_per_1k_exec(), 2)),
    }


def bench_resilience(smoke=False):
    """Fault-tolerance plane costs.

    `recovery_seconds`: crash-only manager restart — construct a fresh
    manager on a workdir holding a snapshot + persistent tail, restore,
    replay the tail, and serve the first Poll (the in-process analog of
    the chaos harness's SIGKILL cycle; tools/chaos.py measures the
    full-subprocess number).  `cold_recovery_seconds` is the same
    workdir without snapshots (full-corpus replay) for the speedup.
    `failover_seconds`: injected device fault → first CPU-backed
    decision block served, engine state migrated."""
    import shutil
    import tempfile
    import time as _time

    from syzkaller_tpu.manager.config import Config
    from syzkaller_tpu.manager.manager import Manager
    from syzkaller_tpu.resilience import ResilientEngine, chaos
    from syzkaller_tpu.sys.table import load_table

    table = load_table(files=["probe.txt"])
    n = 48 if smoke else 256
    tail = max(4, n // 8)
    inputs = chaos.synth_inputs(table, n, seed=21)
    acked = {inp[0]: inp for inp in inputs}
    base = tempfile.mkdtemp(prefix="syz-bench-resil-")
    out = {}
    try:
        w = os.path.join(base, "w")
        mgr = Manager(Config(**chaos.manager_config(w, 0)), table=table)
        for inp in inputs[: n - tail]:
            chaos._admit_direct(mgr, inp)
        mgr.checkpointer.snapshot_once()
        for inp in inputs[n - tail:]:
            chaos._admit_direct(mgr, inp)
        mgr.server.close()
        mgr.dstream.stop()
        if mgr.coalescer is not None:
            mgr.coalescer.stop()
        wcold = os.path.join(base, "wcold")
        shutil.copytree(w, wcold)
        shutil.rmtree(os.path.join(wcold, "snapshots"))

        def recover(workdir):
            t0 = _time.monotonic()
            m = Manager(Config(**chaos.manager_config(workdir, 0)),
                        table=table)
            for data in list(m.candidates):
                inp = acked.get(data)
                if inp is not None:
                    chaos._admit_direct(m, inp)
            m.rpc_poll({"name": "bench"})
            dt = _time.monotonic() - t0
            size = len(m.corpus)
            m.server.close()
            m.dstream.stop()
            if m.coalescer is not None:
                m.coalescer.stop()
            return dt, size

        t_restored, size_r = recover(w)
        t_cold, size_c = recover(wcold)
        if size_r != size_c:     # loss would invalidate the comparison
            out["recovery_corpus_mismatch"] = [size_r, size_c]
        out["recovery_seconds"] = round(t_restored, 3)
        out["cold_recovery_seconds"] = round(t_cold, 3)
        out["recovery_speedup_vs_cold"] = round(t_cold / t_restored, 2)

        from syzkaller_tpu.cover.engine import CoverageEngine
        from syzkaller_tpu.fuzzer.device_ct import DecisionStream

        def make_engine():
            return CoverageEngine(npcs=1 << 12, ncalls=table.count,
                                  corpus_cap=512)

        eng = ResilientEngine(make_engine(), make_engine,
                              probe_interval=0.0)
        stream = DecisionStream(eng, per_row=16, hot_slots=64,
                                corpus_rows=32, entropy_words=1024,
                                autostart=False)
        eng._on_swap = lambda d: stream.rebind()
        idx = (np.arange(16)[None, :] * 3
               + np.arange(8)[:, None] * 80).astype(np.int32)
        eng.admit_if_new(np.arange(8, dtype=np.int32), idx,
                         np.ones_like(idx, bool))
        stream.refill_once()
        eng.injector.arm()
        t0 = _time.monotonic()
        # the fault fires on the next dispatch; the first CPU-backed
        # block (fallback compile included) ends the clock
        stream.refill_once()
        draws = stream.take(-1, 16)
        out["failover_seconds"] = round(_time.monotonic() - t0, 3)
        assert eng.degraded and len(draws) == 16
        eng.injector.disarm()
        stream.stop()
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return out


def bench_autopilot(smoke=False):
    """Control-plane costs.

    `autopilot_detect_seconds` / `autopilot_recover_seconds`: the chaos
    compound-failure cycle (2 of N VM threads killed + backend flap +
    wedged campaign) measured fault-injected → first action fired and
    fault-injected → fully remediated (capacity restored, backend
    promoted, campaign rotated).

    `admission_shed_rate_overload`: the overload-protection contract —
    at ~10x admission overload (tiny bounded queue, artificially slow
    drain, 3x queue-cap concurrent submitters) the manager SHEDS
    instead of queueing toward an OOM, and p99 admit latency stays
    bounded (`admission_p99_admit_seconds_overload`)."""
    import shutil
    import tempfile
    import threading
    import time as _time

    from syzkaller_tpu.manager.config import Config
    from syzkaller_tpu.manager.manager import Manager
    from syzkaller_tpu.resilience import chaos
    from syzkaller_tpu.sys.table import load_table

    out = {}
    base = tempfile.mkdtemp(prefix="syz-bench-autopilot-")
    try:
        cyc = chaos.run_autopilot_cycle(base, n_inputs=16 if smoke else 48)
        out["autopilot_detect_seconds"] = cyc["autopilot_detect_seconds"]
        out["autopilot_recover_seconds"] = cyc["autopilot_recover_seconds"]

        # admission overload: bounded queue + deadline shedding
        table = load_table(files=["probe.txt"])
        n = 192 if smoke else 768
        inputs = chaos.synth_inputs(table, n, seed=29)
        w = os.path.join(base, "w-overload")
        cfg = Config(**chaos.manager_config(
            w, 0, snapshot_interval=0.0, admit_batch=8,
            admit_queue_cap=8, admit_shed_deadline=0.25,
            autopilot=False))
        mgr = Manager(cfg, table=table)
        try:
            # slow the raw engine dispatch (not the ResilientEngine
            # wrapper — patching through the proxy would re-resolve to
            # the patch and recurse)
            prim = getattr(mgr.engine, "primary", mgr.engine)
            orig = prim.admit_batch

            def slow_admit(*a, **k):
                _time.sleep(0.01)       # ~10x slower than arrivals
                return orig(*a, **k)

            prim.admit_batch = slow_admit
            lat = []
            lat_mu = threading.Lock()
            nthreads = 24

            def storm(chunk):
                for inp in chunk:
                    t0 = _time.monotonic()
                    chaos._admit_direct(mgr, inp, name="overload")
                    dt = _time.monotonic() - t0
                    with lat_mu:
                        lat.append(dt)

            threads = [threading.Thread(
                target=storm, args=(inputs[i::nthreads],), daemon=True)
                for i in range(nthreads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            shed = int(mgr._c_shed.value)
            lat.sort()
            out["admission_shed_rate_overload"] = round(shed / n, 3)
            out["admission_p99_admit_seconds_overload"] = round(
                lat[int(0.99 * (len(lat) - 1))], 3) if lat else None
        finally:
            mgr.stop()
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return out


def bench_synth(smoke=False):
    """Device-resident program synthesis vs the host generator: the
    synth_block megakernel emits B complete exec-bytecode programs per
    dispatch (resolve included — provenance unpack and all), measured
    against per-program host Python generate+serialize on the same
    backend.  Also pins warm recompiles across the timed loop with the
    tables GROWING mid-stream (contents-only appends)."""
    import time as _t

    from syzkaller_tpu import prog as P
    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.synth import DeviceSynth
    from syzkaller_tpu.prog.encodingexec import serialize_for_exec
    from syzkaller_tpu.sys.table import load_table
    from syzkaller_tpu.vet.runtime import CompileCounter

    table = load_table(files=["probe.txt"])
    eng = CoverageEngine(npcs=1 << 12, ncalls=table.count,
                         corpus_cap=64, seed=5)
    eng.set_enabled(range(table.count))
    ds = DeviceSynth(eng, table, batch=64 if smoke else 256)
    rand = P.Rand(np.random.default_rng(9))
    ds.build_templates(range(table.count), rand)
    rows = 0
    while rows < 8:
        rows += bool(ds.add_program(P.generate(rand, table, 6)))

    # host generator baseline: the per-program inner loop the
    # megakernel retires (generate + exec serialization)
    seconds = 0.4 if smoke else 2.0
    t0 = _t.monotonic()
    m = 0
    while _t.monotonic() - t0 < seconds:
        serialize_for_exec(P.generate(rand, table, 6))
        m += 1
    host_rate = m / (_t.monotonic() - t0)

    ds.resolve(ds.dispatch())            # warm compile
    grown = 0
    with CompileCounter() as cc:
        t0 = _t.monotonic()
        n = 0
        while _t.monotonic() - t0 < seconds:
            n += len(ds.resolve(ds.dispatch()).progs)
            if grown < 2:                # grow mid-loop: contents only
                grown += bool(ds.add_program(
                    P.generate(rand, table, 6)))
        dev_rate = n / (_t.monotonic() - t0)
    return {
        "programs_per_sec_device": round(dev_rate, 1),
        "programs_per_sec_host": round(host_rate, 1),
        "synth_speedup": round(dev_rate / max(host_rate, 1e-9), 2),
        "synth_recompiles_warm": cc.count,
        "synth_templates": ds.n_templates,
    }


def bench_sharded(call_ids, pc_idx, valid, npcs=NPCS, seconds=SECONDS,
                  smoke=False):
    """Mesh-plane throughput: the SAME update_batch stream through a
    serial and a PC-axis-sharded engine, timed, with warm recompiles
    pinned at 0 and the exported frontiers asserted bit-identical (the
    sharded path must never buy speed with divergence).  Shards over
    min(8, available) devices; on a 1-device backend it degrades to the
    serial engine so the JSON schema survives any host."""
    import jax

    from syzkaller_tpu.cover.engine import CoverageEngine, pc_mesh
    from syzkaller_tpu.vet.runtime import CompileCounter

    n_dev = 1
    while n_dev * 2 <= min(8, len(jax.devices())):
        n_dev *= 2
    mesh = pc_mesh(n_dev, "") if n_dev > 1 else None
    nbatch = call_ids.shape[0]

    def run(eng):
        for bi in range(nbatch):         # warm every batch shape
            np.asarray(eng.update_batch(call_ids[bi], pc_idx[bi],
                                        valid[bi]).has_new)
        with CompileCounter() as cc:
            n = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < seconds:
                bi = n % nbatch
                np.asarray(eng.update_batch(
                    call_ids[bi], pc_idx[bi], valid[bi]).has_new)
                n += 1
            dt = time.perf_counter() - t0
        return call_ids.shape[1] * n / dt, cc.count

    serial = CoverageEngine(npcs=npcs, ncalls=NCALLS, corpus_cap=8)
    rate_serial, rc_serial = run(serial)
    if mesh is not None:
        sharded = CoverageEngine(npcs=npcs, ncalls=NCALLS, corpus_cap=8,
                                 mesh=mesh)
        rate_sharded, rc_sharded = run(sharded)
        a, b = serial.export_state(), sharded.export_state()
        for key in ("max_cover", "corpus_cover", "flakes"):
            assert np.array_equal(np.asarray(a[key]),
                                  np.asarray(b[key])), \
                f"sharded engine diverged in {key}"
    else:
        rate_sharded, rc_sharded = rate_serial, rc_serial
    return {
        "signal_diff_prio_updates_per_sec_sharded": round(rate_sharded, 1),
        "sharded_devices": n_dev,
        # per-chip efficiency vs ideal linear scaling of the serial
        # rate (virtual CPU devices share cores, so < 1 here; the
        # number exists to make TPU-pod runs comparable)
        "sharded_scaling_per_chip": round(
            rate_sharded / (rate_serial * n_dev), 3),
        "sharded_recompiles_warm": rc_sharded + rc_serial,
    }


def bench_hub_sync(nprogs=512, smoke=False):
    """Hub exchange throughput over the real RPC wire, plus the sketch
    filter's acceptance numbers: manager A pushes nprogs programs with
    per-program covered-block sets; manager B's sketch already covers
    the even half, so the hub must withhold exactly those (filtered)
    and ship every odd program (a missing one is an exchange false
    negative — the number this bench pins at 0)."""
    import shutil
    import tempfile

    from syzkaller_tpu import rpc as _rpc
    from syzkaller_tpu.hub.hub import Hub
    from syzkaller_tpu.mesh.sketch import encode_blocks

    nprogs = 64 if smoke else nprogs
    rng = np.random.default_rng(31)
    progs = [bytes(rng.integers(0, 256, size=64, dtype=np.uint8))
             for _ in range(nprogs)]
    blocks = [np.arange(i * 4, i * 4 + 4, dtype=np.uint64)
              for i in range(nprogs)]
    b_covered = np.concatenate(blocks[0::2])

    workdir = tempfile.mkdtemp(prefix="syz-bench-hub-")
    hub = Hub(workdir, key="bench")
    hub.server.serve_background()
    try:
        cli = {n: _rpc.RpcClient(hub.addr, timeout=30.0)
               for n in ("a", "b")}
        for n, c in cli.items():
            c.call("Hub.Connect", {"name": n, "key": "bench",
                                   "fresh": True})
        t0 = time.perf_counter()
        # A pushes everything (blocks attached) + its full sketch
        a_sketch = encode_blocks(np.concatenate(blocks))
        cli["a"].call("Hub.Sync", {
            "name": "a", "key": "bench",
            "add": [_rpc.b64(p) for p in progs],
            "blocks": [encode_blocks(b) for b in blocks],
            "sketch": a_sketch, "sketch_reset": True})
        # B announces the even half as covered, then drains the hub
        got: list[bytes] = []
        filtered = 0
        r = cli["b"].call("Hub.Sync", {
            "name": "b", "key": "bench", "add": [],
            "sketch": encode_blocks(b_covered), "sketch_reset": True})
        while True:
            got += [_rpc.unb64(p) for p in r["progs"]]
            filtered += r["filtered"]
            if not r["more"]:
                break
            r = cli["b"].call("Hub.Sync", {"name": "b", "key": "bench",
                                           "add": []})
        dt = time.perf_counter() - t0
        for c in cli.values():
            c.close()
    finally:
        hub.close()
        shutil.rmtree(workdir, ignore_errors=True)

    want = set(progs[1::2])              # programs carrying new blocks
    fn = len(want - set(got))            # withheld-but-needed = FN
    return {
        "hub_sync_programs_per_sec": round((nprogs + len(got)) / dt, 1),
        "hub_sketch_filtered": filtered,
        "hub_sketch_fn": fn,
        "hub_sync_corpus": nprogs,
    }


def _stage(name):
    sys.stderr.write(f"[bench] {name}\n")
    sys.stderr.flush()


def bench_tsdb(smoke=False):
    """Fleet-observatory cost: the tsdb rollup is ONE fused dispatch
    per wall-clock tick (never per exec) folding every stat slot's
    delta into the three retention tiers, and a scrape is ONE
    device→host transfer of the (S, W) ring.  Warm recompiles across
    the run must be 0 — the tick operands are traced scalars."""
    import jax.numpy as jnp

    from syzkaller_tpu.observe import DeviceTsdb
    from syzkaller_tpu.telemetry import DeviceStats
    from syzkaller_tpu.vet.runtime import CompileCounter

    ds = DeviceStats()
    d = DeviceTsdb([ds])
    n = 64 if smoke else 1024
    vec = np.zeros(ds.nslots, np.int32)
    d.sample_now()                       # build + compile the kernel
    with CompileCounter() as cc:
        t0 = time.perf_counter()
        for _t in range(n):
            vec[0] += 1
            # copy: jnp.asarray may alias the numpy buffer on CPU and
            # vec mutates under the async dispatch
            ds.vec = jnp.asarray(vec.copy())
            d.sample_now()
        dt = time.perf_counter() - t0
    t1 = time.perf_counter()
    d.scrape()
    return {"tsdb_samples_per_sec": round(n / dt, 1),
            "tsdb_scrape_seconds": round(time.perf_counter() - t1, 5),
            "tsdb_recompiles_warm": int(cc.count)}


def bench_corpus_tiers(smoke=False):
    """Tiered corpus hierarchy: a corpus ≥100x the fixed device cap
    fuzzed through the hot tables with eviction-kernel demotion to the
    warm mmap'd segment log and contents-only promotion back.  Reports

      * `tier_hot_hit_rate`      — resolve-path hot-tier hits over a
                                   recency-skewed working set (the
                                   presubmit gates ≥ 0.9);
      * `tier_recompiles_warm`   — CompileCounter over the ENTIRE
                                   over-cap + promote phase (gated 0:
                                   warm traffic is contents-only swaps
                                   behind fixed dispatch signatures);
      * `tier_promotions_per_sec`— warm→hot promotion throughput
                                   (read_rows mmap gather + one swap
                                   dispatch per batch);
      * `tier_dispatch_constancy`— late/early mean admission-batch
                                   wall time; ~1.0 means dispatch cost
                                   does not grow with warm-tier size;
      * `tier_frontier_bit_exact`— fused tiered fuzz ticks vs an
                                   unbounded-table oracle on a subset
                                   stream: identical admission verdicts
                                   and max/corpus-cover frontiers.
    """
    import tempfile

    from syzkaller_tpu.corpus import TierManager, WarmStore
    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.pcmap import DeviceKeyMirror, PcMap
    from syzkaller_tpu.vet.runtime import CompileCounter

    rng = np.random.default_rng(29)
    cap = 64 if smoke else 1024
    W = 16                                 # signal words per row
    nbatch = 32 if smoke else 256
    total = 100 * cap                      # ≥100x the device cap
    tmp = tempfile.mkdtemp(prefix="syz-tier-bench-")

    eng = CoverageEngine(npcs=W * 32, ncalls=8, corpus_cap=cap,
                         batch=nbatch, max_pcs_per_exec=8)
    tm = TierManager(WarmStore(os.path.join(tmp, "warm")), engine=eng)

    def batch_bitmaps(n):
        bm = np.zeros((n, eng.W), np.uint32)
        bm[:, :W] = rng.integers(1, 2 ** 32, (n, W), dtype=np.uint32)
        return bm

    # phase 1 — grow a 100x-cap corpus through the admission path.
    # Warmup batches outside the counter compile every admission
    # signature (under-cap append, partial-over, full-over demote);
    # everything after is gated zero-recompile.  Batch wall times feed
    # the constancy ratio.
    def grow():
        nonlocal owner
        rows = eng.merge_corpus(rng.integers(0, 8, nbatch)
                                .astype(np.int64), batch_bitmaps(nbatch))
        tm.set_owners(rows, np.arange(owner, owner + nbatch))
        owner += nbatch

    owner = 0
    nwarm = cap // nbatch + 2
    for _ in range(nwarm):
        grow()
    nsteps = total // nbatch - nwarm
    times = np.zeros(nsteps)

    # warm the promote path too: both pow2 swap buckets the probe
    # batches can hit (1..8 -> 8, 9..16 -> 16 warm misses), against
    # ids that really are warm-resident right now
    warm_ids = np.setdiff1d(np.arange(owner),
                            tm.row_owner[tm.row_owner >= 0])
    assert (tm.resolve_rows(warm_ids[:1]) >= 0).all()
    assert (tm.resolve_rows(warm_ids[1:13]) >= 0).all()
    base_promos = tm.stat_promotions
    base_hits, base_misses = tm.stat_hot_hits, tm.stat_hot_misses

    with CompileCounter() as cc:
        for i in range(nsteps):
            t0 = time.perf_counter()
            grow()
            times[i] = time.perf_counter() - t0

        # phase 2 — recency-skewed resolve traffic: ~95% of each probe
        # batch targets owners currently hot (the most recently
        # admitted/promoted), the rest reach back into the warm log;
        # every warm miss promotes through the fixed-shape swap
        nprobe = 40 if smoke else 200
        probe_b = 16
        t0 = time.perf_counter()
        for _ in range(nprobe):
            hot_now = tm.row_owner[tm.row_owner >= 0]
            recent = rng.choice(hot_now, probe_b - 1)
            deep = rng.integers(0, owner - cap, 1)
            got = tm.resolve_rows(np.concatenate([recent, deep]))
            assert (got >= 0).all()
        probe_dt = time.perf_counter() - t0
    hits = tm.stat_hot_hits - base_hits
    misses = tm.stat_hot_misses - base_misses
    hit_rate = hits / max(1, hits + misses)

    # phase 3 — frontier bit-exactness: fused tiered ticks vs an
    # unbounded-table oracle over the same exec stream
    n_execs = 1000 if smoke else 10_000
    B, K = 8, 16

    def mk(c):
        e = CoverageEngine(npcs=1 << 12, ncalls=8, corpus_cap=c,
                           batch=B, max_pcs_per_exec=K)
        m = DeviceKeyMirror(PcMap(1 << 12), put=e.put_replicated)
        return e, m

    tiered, mir_t = mk(32)
    TierManager(WarmStore(os.path.join(tmp, "warm2")), engine=tiered)
    oracle, mir_o = mk(1 << 14)
    bit_exact = True
    srng = np.random.default_rng(31)
    for it in range(n_execs // B):
        if it % 4 == 0:                    # fresh signal batch
            win = (np.arange(K, dtype=np.uint32)[None, :]
                   + np.arange(B, dtype=np.uint32)[:, None] * K
                   + it * B * K + 1)
        else:                              # duplicate churn
            win = (np.arange(K, dtype=np.uint32)[None, :]
                   + np.arange(B, dtype=np.uint32)[:, None] * K
                   + (it - it % 4) * B * K + 1)
        win = win.astype(np.uint32)
        counts = np.full((B,), K, np.int32)
        cids = srng.integers(0, 8, B).astype(np.int32)
        prev = np.full((4,), -1, np.int32)
        live = np.arange(K)[None, :] < counts[:, None]
        mir_t.ensure(win[live])
        mir_o.ensure(win[live])
        rt = tiered.fuzz_tick(win, counts, cids, prev, mir_t)
        ro = oracle.fuzz_tick(win, counts, cids, prev, mir_o)
        if not np.array_equal(rt.has_new, ro.has_new):
            bit_exact = False
    bit_exact = (bit_exact
                 and np.array_equal(np.asarray(tiered.max_cover),
                                    np.asarray(oracle.max_cover))
                 and np.array_equal(np.asarray(tiered.corpus_cover),
                                    np.asarray(oracle.corpus_cover)))

    tenth = max(1, nsteps // 10)
    constancy = float(np.mean(times[-tenth:]) / np.mean(times[:tenth]))
    return {
        "tier_corpus_records": owner,
        "tier_corpus_cap": cap,
        "tier_rows_warm": int(tm.store.rows_warm),
        "tier_bytes_warm": int(tm.store.bytes_warm),
        "tier_hot_hit_rate": round(hit_rate, 4),
        "tier_promotions_per_sec": round((tm.stat_promotions
                                          - base_promos)
                                         / max(probe_dt, 1e-9), 1),
        "tier_recompiles_warm": int(cc.count),
        "tier_dispatch_constancy": round(constancy, 3),
        "tier_frontier_bit_exact": bool(bit_exact),
    }


def bench_fuzz_tick(smoke=False):
    """Single-dispatch fuzz tick: engine.fuzz_tick fuses
    ingest-translate → signal-diff → admission gate/merge → tsdb bump →
    decision draws into ONE host→device dispatch.  This stage proves
    the fusion on the same batch stream three ways:

      * `fuzz_tick_parity` — the fused frontier (max/corpus cover +
        signal matrix + verdict stream) is BIT-exact vs the unfused
        ingest_update_slabs + admit_slabs pair (presubmit gates this);
      * `dispatches_per_tick_*` — counted by a DispatchProfiler (the
        /profile/dispatches view), the fused path crosses the host
        boundary once per batch where the unfused pair crosses twice;
      * throughput on both paths, same workload.

    `dispatch_top` is the profiler's top-10 table over this stage
    (name, calls, seconds_sum, recompiles) — the flat view the fleet
    console renders from /profile/dispatches."""
    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.pcmap import DeviceKeyMirror, PcMap
    from syzkaller_tpu.observe import DispatchProfiler

    npcs, nkeys = 1 << 12, 3000
    n = 48 if smoke else 512
    rng = np.random.default_rng(21)

    def mk():
        eng = CoverageEngine(npcs=npcs, ncalls=16, corpus_cap=4096)
        pm = PcMap(npcs)
        pm.preseed(np.arange(0, nkeys, dtype=np.uint64))
        mirror = DeviceKeyMirror(pm, put=eng.put_replicated)
        mirror.refresh()
        return eng, mirror

    batches = []
    for _ in range(n):
        win = rng.integers(0, nkeys, (8, 32)).astype(np.uint32)
        counts = rng.integers(1, 33, 8).astype(np.int32)
        cids = rng.integers(0, 16, 8).astype(np.int32)
        prev = rng.integers(-1, 16, 8).astype(np.int32)
        batches.append((win, counts, cids, prev))

    fus_eng, fus_m = mk()
    unf_eng, unf_m = mk()
    prof = DispatchProfiler()
    prof.attach(fus_eng)
    prof.attach(unf_eng)

    def counts_total():
        return sum(d["count"]
                   for d in prof.snapshot()["dispatches"].values())

    # warm both shape closures outside the counted window
    w, c, ci, pv = batches[0]
    fus_eng.fuzz_tick(w, c, ci, pv, fus_m)
    unf_eng.ingest_update_slabs(w, c, ci, unf_m)
    unf_eng.admit_slabs(w, c, ci, pv, unf_m)

    base = counts_total()
    t0 = time.perf_counter()
    fused_verdicts = []
    for w, c, ci, pv in batches[1:]:
        res = fus_eng.fuzz_tick(w, c, ci, pv, fus_m)
        fused_verdicts.append(res.has_new)
    fused_dt = time.perf_counter() - t0
    fused_dispatches = counts_total() - base

    base = counts_total()
    t0 = time.perf_counter()
    unf_verdicts = []
    for w, c, ci, pv in batches[1:]:
        unf_eng.ingest_update_slabs(w, c, ci, unf_m)
        hn, _rows, _ch = unf_eng.admit_slabs(w, c, ci, pv, unf_m)
        unf_verdicts.append(hn)
    unf_dt = time.perf_counter() - t0
    unf_dispatches = counts_total() - base

    ticks = len(batches) - 1
    parity = (
        all(np.array_equal(a, b)
            for a, b in zip(fused_verdicts, unf_verdicts))
        and np.array_equal(np.asarray(fus_eng.max_cover),
                           np.asarray(unf_eng.max_cover))
        and np.array_equal(np.asarray(fus_eng.corpus_cover),
                           np.asarray(unf_eng.corpus_cover))
        and np.array_equal(np.asarray(fus_eng.corpus_mat),
                           np.asarray(unf_eng.corpus_mat))
        and fus_eng.corpus_len == unf_eng.corpus_len)

    snap = prof.snapshot()
    top = sorted(((n_, d) for n_, d in snap["dispatches"].items()
                  if d["count"]),
                 key=lambda kv: kv[1]["sum_seconds"], reverse=True)[:10]
    dispatch_top = [
        {"name": name, "calls": d["count"],
         "seconds_sum": round(d["sum_seconds"], 5),
         "recompiles": snap["recompiles"].get(name, 0)}
        for name, d in top]
    return {
        "fuzz_tick_parity": bool(parity),
        "dispatches_per_tick_fused": round(fused_dispatches / ticks, 3),
        "dispatches_per_tick_unfused": round(unf_dispatches / ticks, 3),
        "fuzz_tick_batches_per_sec": round(ticks / fused_dt, 1),
        "fuzz_tick_unfused_batches_per_sec": round(ticks / unf_dt, 1),
        "dispatch_top": dispatch_top,
    }


def bench_san_overhead(smoke=False):
    """syz-san runtime-plane cost: the same fused fuzz-tick loop run
    unarmed and then under SYZ_SAN=1 (shadow checker wrapped around
    every dispatch closure + donation poison sweep).  Reported as
    `san_overhead_pct` so the sanitizer's tax is visible in every
    BENCH_*.json — the opt-in only stays cheap if drift is measured."""
    import os

    from syzkaller_tpu import san
    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.fuzzer.pcmap import DeviceKeyMirror, PcMap

    npcs, nkeys = 1 << 12, 3000
    n = 48 if smoke else 384
    rng = np.random.default_rng(23)
    batches = []
    for _ in range(n):
        win = rng.integers(0, nkeys, (8, 32)).astype(np.uint32)
        counts = rng.integers(1, 33, 8).astype(np.int32)
        cids = rng.integers(0, 16, 8).astype(np.int32)
        prev = rng.integers(-1, 16, 8).astype(np.int32)
        batches.append((win, counts, cids, prev))

    def run(armed: bool) -> float:
        prev_env = os.environ.get("SYZ_SAN")
        os.environ["SYZ_SAN"] = "1" if armed else "0"
        try:
            eng = CoverageEngine(npcs=npcs, ncalls=16, corpus_cap=4096)
            pm = PcMap(npcs)
            pm.preseed(np.arange(0, nkeys, dtype=np.uint64))
            mirror = DeviceKeyMirror(pm, put=eng.put_replicated)
            mirror.refresh()
            if armed:
                san.attach(eng)     # idempotent with _build's self-arm
            w, c, ci, pv = batches[0]
            eng.fuzz_tick(w, c, ci, pv, mirror)       # warm the closure
            t0 = time.perf_counter()
            for w, c, ci, pv in batches[1:]:
                eng.fuzz_tick(w, c, ci, pv, mirror)
            return time.perf_counter() - t0
        finally:
            if prev_env is None:
                os.environ.pop("SYZ_SAN", None)
            else:
                os.environ["SYZ_SAN"] = prev_env

    plain_dt = run(armed=False)
    armed_dt = run(armed=True)
    findings = san.report.total
    return {
        "san_overhead_pct": round(
            (armed_dt - plain_dt) / plain_dt * 100.0, 1),
        "san_armed_batches_per_sec": round((n - 1) / armed_dt, 1),
        "san_findings_clean_run": findings,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale CPU-only shape/import smoke "
                         "(presubmit gate), same code paths and JSON "
                         "schema on tiny configs")
    args = ap.parse_args(argv)

    extras = {}
    if args.smoke:
        # smoke runs the probe too (it is cheap on CPU): the presubmit
        # forced-failure run exercises the fallback path end to end
        note = _ensure_backend()
        os.environ["JAX_PLATFORMS"] = "cpu"
        _apply_smoke()
        extras["config"] = "smoke"
        if note:
            extras["backend"] = note
    else:
        note = _ensure_backend()
        if note:
            extras["backend"] = note

    rng = np.random.default_rng(42)
    call_ids, pc_idx, valid = make_workload(rng)
    _stage("cpu baseline")
    cpu_rate = bench_cpu(call_ids, pc_idx, valid, seconds=SECONDS)
    _stage("device 64k")
    dev_rate = bench_device(call_ids, pc_idx, valid, npcs=NPCS,
                            seconds=SECONDS)

    # 1M-PC config (BASELINE config #5: "1M-PC sparse bitmap").  The
    # TPU-first architecture handles the sparse 1M-PC universe the way
    # production does (DeviceSignal): the vectorized PcMap hashes raw
    # PCs into a DENSE observed-set index space (capacity 128k — 2× the
    # reference's own 64k per-call KCOV cap), and the fused device step
    # runs at the dense width.  Per-exec device work is then
    # proportional to the live signal set, not the universe — the
    # "touch only what the workload references" sparse formulation.
    big_npcs = 1 << (17 if not args.smoke else 13)
    big_sec = 3.0 if not args.smoke else SECONDS
    _stage("device 1M-PC (observed-set, dense 128k)")
    big = make_workload(np.random.default_rng(7), npcs=big_npcs,
                        nbatch=4, b=B)
    extras["updates_per_sec_1m_pc"] = round(
        bench_device(*big, npcs=big_npcs, seconds=big_sec), 1)
    extras["updates_per_sec_1m_pc_config"] = (
        "observed-set: 1M-PC universe hashed to dense 128k live set "
        "(production DeviceSignal architecture); _dense_fullwidth is "
        "the r02-comparable raw 1M-wide config")
    # honesty extra: the raw dense-1M-wide step (no observed-set
    # mapping), bandwidth-bound on the 16×-wider bitmaps — this is the
    # shape BENCH_r02's updates_per_sec_1m_pc measured — and the
    # word-block-sparse step on the SAME workload, which gathers only
    # the blocks a batch touches so per-step work follows live signal
    full_npcs = 1 << (20 if not args.smoke else 14)
    full_b = 256 if not args.smoke else 32
    _stage("device 1M-PC (dense full-width)")
    big = make_workload(np.random.default_rng(7), npcs=full_npcs,
                        nbatch=4, b=full_b)
    dense_full = bench_device(*big, npcs=full_npcs, seconds=big_sec)
    extras["updates_per_sec_1m_pc_dense_fullwidth"] = round(dense_full, 1)
    _stage("device 1M-PC (word-block sparse)")
    sparse_full = bench_device_sparse(*big, npcs=full_npcs,
                                      seconds=big_sec)
    extras["updates_per_sec_1m_pc_blocksparse"] = round(sparse_full, 1)
    extras["blocksparse_speedup"] = round(sparse_full / dense_full, 2)
    # instrumented replay of the same workload through the production
    # engine path: the device stat vector's sparse/dense dispatch and
    # fallback counts ship in the JSON next to the kernel-only rate
    from syzkaller_tpu.cover.engine import CoverageEngine
    from syzkaller_tpu.telemetry import DeviceStats

    ds = DeviceStats()
    eng = CoverageEngine(npcs=full_npcs, ncalls=NCALLS, corpus_cap=8,
                         batch=full_b, max_pcs_per_exec=K,
                         max_touched_blocks=512, telemetry=ds)
    for bi in range(big[0].shape[0]):
        eng.update_batch_sparse(big[0][bi], big[1][bi], big[2][bi])
    sparse_telem = ds.snapshot()
    _stage("admission coalescer")
    adm_extras, adm_snap = bench_admission(
        n_inputs=64 if args.smoke else 1536,
        nthreads=4 if args.smoke else 48, npcs=NPCS)
    extras.update(adm_extras)
    if adm_snap is not None:
        # the manager/engine telemetry snapshot (dispatch counts,
        # admission latency histogram, sparse-fallback counters) rides
        # BENCH_*.json next to the throughput numbers
        extras["telemetry"] = {"admission_manager": adm_snap,
                               "blocksparse_engine": sparse_telem}
    _stage("new-cov quality replay (zero-copy ingest)")
    extras.update(bench_new_cov_quality(np.random.default_rng(11),
                                        nexecs=(8 if args.smoke else 16) * B))
    _stage("fused fuzz tick (single dispatch)")
    extras.update(bench_fuzz_tick(smoke=args.smoke))
    _stage("syz-san overhead (runtime sanitizer)")
    extras.update(bench_san_overhead(smoke=args.smoke))
    _stage("corpus scale")
    extras.update(bench_corpus_scale(np.random.default_rng(13),
                                     C=2048 if args.smoke else 100_000))
    _stage("decision stream")
    extras.update(bench_decision_stream(
        seconds=0.5 if args.smoke else 2.0, smoke=args.smoke))
    _stage("device program synthesis")
    extras.update(bench_synth(smoke=args.smoke))
    _stage("sharded engine (mesh plane)")
    extras.update(bench_sharded(call_ids, pc_idx, valid, npcs=NPCS,
                                seconds=0.5 if args.smoke else SECONDS,
                                smoke=args.smoke))
    _stage("hub exchange (sketch filter)")
    extras.update(bench_hub_sync(smoke=args.smoke))
    _stage("triage dedup")
    extras.update(bench_triage(np.random.default_rng(17),
                               smoke=args.smoke))
    _stage("repro scheduler")
    extras.update(bench_repro_rounds(smoke=args.smoke))
    _stage("campaign plane")
    extras.update(bench_campaign(smoke=args.smoke))
    _stage("resilience plane")
    extras.update(bench_resilience(smoke=args.smoke))
    _stage("autopilot control plane")
    extras.update(bench_autopilot(smoke=args.smoke))
    _stage("fleet observatory (tsdb rollup)")
    extras.update(bench_tsdb(smoke=args.smoke))
    _stage("tiered corpus hierarchy")
    extras.update(bench_corpus_tiers(smoke=args.smoke))
    # static-analysis gate trajectory: the BENCH_*.json series records
    # the vet finding counts alongside throughput, so a PR that buys
    # speed by parking P0s in the baseline shows up in the history
    _stage("vet")
    from syzkaller_tpu.vet import core as vet_core

    vrep = vet_core.run_repo()
    vc = vrep.counts()
    extras["vet_findings_total"] = vc["total"]
    extras["vet_findings"] = {
        "p0_unbaselined": vc["p0_unbaselined"], "p0": vc["p0"],
        "p1": vc["p1"], "baselined": vc["baselined"],
        "by_pass": vc["by_pass"]}
    _stage("done")

    print(json.dumps({
        "metric": "signal_diff_prio_updates_per_sec",
        "value": round(dev_rate, 1),
        "unit": "updates/s",
        "vs_baseline": round(dev_rate / cpu_rate, 3),
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
